"""Parallel host-side per-item maps — the text analogue of the native
threaded JPEG decode tier (``ks_decode_jpegs``).

The host text stage (tokenize → n-gram → tf → featurize) is pure
Python, so THREADS cannot parallelize it — the GIL serializes them;
libjpeg could use threads only because C decode releases the GIL.
Workers here are processes, with two deliberate choices:

- **forkserver start method** (spawn fallback): plain ``fork`` from a
  jax-threaded parent is a documented deadlock hazard (jax's runtime
  threads hold locks across the fork).  The forkserver's server process
  is fresh and this module imports nothing heavy, so workers never
  inherit jax state; jax only enters a worker if the mapped callable's
  module imports it during unpickling (import only — no backend init,
  no tunnel contact).
- **one PERSISTENT pool per process**, not a pool per call: streaming
  sweeps call host_map once per stage per batch, and per-call pools
  would pay worker startup (python + module imports) thousands of
  times.  Tasks carry the pickled callable each time (cheap for
  tokenizers; ~MBs for a vocab model, amortized against ~100x more
  batch work) and workers cache the unpickled callable by digest.

Sizing: ``KEYSTONE_HOST_WORKERS`` overrides; default is the CPU count.
With 1 worker (or small inputs, or an unpicklable callable) the map is
plain sequential — zero overhead on single-core hosts.

This module is ALSO the serving fleet's **host map**
(:class:`HostMap`): the registry of machines a cross-host fleet
(``serve/net.py``) may spawn ``keystone worker`` processes on, with
per-host slot budgets the autoscaler's ``add_replica`` respects.  The
two halves share a file because they answer the same question at two
scales — "where does host-side work run?" — per-item maps on THIS
host's cores, worker processes on the fleet's machines.
"""

from __future__ import annotations

import atexit
import hashlib
import os
import pickle
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

_EXECUTOR = None
#: host_map is called from stream prefetch threads as well as the main
#: thread; the lock keeps two racing callers from each building (and
#: one orphaning) a worker pool
_EXECUTOR_LOCK = threading.Lock()
_EXECUTOR_WORKERS = 0
_POOL_WARNED = False
_POOL_SIZE_NOTED = False

#: worker-side: digest → unpickled callable (so the vocab model
#: unpickles once per worker, not once per batch).  Bounded: a sweep of
#: many fitted models must not grow worker RSS without limit.
_FN_CACHE: Dict[bytes, Callable] = {}
_FN_CACHE_CAP = 8


def host_workers() -> int:
    env = os.environ.get("KEYSTONE_HOST_WORKERS")
    if env is not None:
        try:
            return max(1, int(env))
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "KEYSTONE_HOST_WORKERS=%r is not an integer; using 1", env
            )
            return 1
    return os.cpu_count() or 1


def _run_task(digest: bytes, fn_bytes: bytes, chunk: list) -> list:
    fn = _FN_CACHE.get(digest)
    if fn is None:
        fn = pickle.loads(fn_bytes)
        while len(_FN_CACHE) >= _FN_CACHE_CAP:
            _FN_CACHE.pop(next(iter(_FN_CACHE)))  # FIFO eviction
        _FN_CACHE[digest] = fn
    return [fn(x) for x in chunk]


def _get_executor(workers: int):
    """(executor, actual_worker_count) — or (None, 0) when unavailable.
    The pool is created ONCE per process; a later caller requesting a
    different size reuses the existing pool (logged once) rather than
    churning worker startup."""
    global _EXECUTOR, _EXECUTOR_WORKERS, _POOL_WARNED, _POOL_SIZE_NOTED
    with _EXECUTOR_LOCK:
        if _EXECUTOR is None:
            # explicit forkserver/spawn context below — never fork
            import multiprocessing as mp  # lint: allow-proc-spawn
            from concurrent.futures import ProcessPoolExecutor

            methods = mp.get_all_start_methods()
            method = "forkserver" if "forkserver" in methods else "spawn"
            try:
                _EXECUTOR = ProcessPoolExecutor(
                    max_workers=workers, mp_context=mp.get_context(method)
                )
            except Exception:
                if not _POOL_WARNED:
                    import logging

                    logging.getLogger(__name__).warning(
                        "host_map worker pool unavailable; mapping "
                        "sequentially",
                        exc_info=True,
                    )
                    _POOL_WARNED = True
                return None, 0
            _EXECUTOR_WORKERS = workers
            atexit.register(shutdown)
        elif workers != _EXECUTOR_WORKERS and not _POOL_SIZE_NOTED:
            # separate flag from _POOL_WARNED: this notice must not
            # suppress (or be suppressed by) the pool-unavailable warning
            import logging

            logging.getLogger(__name__).info(
                "host_map pool already sized at %d workers; request for "
                "%d reuses it (pools are per-process singletons)",
                _EXECUTOR_WORKERS,
                workers,
            )
            _POOL_SIZE_NOTED = True
        return _EXECUTOR, _EXECUTOR_WORKERS


def shutdown() -> None:
    """Stop the worker pool (idempotent; a later host_map restarts it)."""
    global _EXECUTOR
    with _EXECUTOR_LOCK:
        if _EXECUTOR is not None:
            _EXECUTOR.shutdown(wait=False, cancel_futures=True)
            _EXECUTOR = None


def host_map(
    fn: Callable,
    items: Sequence,
    workers: Optional[int] = None,
    min_items: int = 512,
) -> List:
    """``[fn(x) for x in items]``, parallelized over the persistent
    worker pool when the input is large enough to amortize task
    overhead.  Order is preserved; results are identical to the
    sequential map (pinned by tests/test_hostmap.py).  Falls back to
    sequential for small inputs, single-core hosts, unpicklable
    callables, and pool-infrastructure failures.  An exception raised
    by ``fn`` itself propagates unchanged, exactly as the sequential
    map would raise it — data errors must not be retried or demoted."""
    items = items if isinstance(items, list) else list(items)
    w = host_workers() if workers is None else max(1, int(workers))
    if w <= 1 or len(items) < max(min_items, 2):
        return [fn(x) for x in items]
    try:
        fn_bytes = pickle.dumps(fn)
    except Exception:
        # closures/lambdas: sequential rather than failing the map
        return [fn(x) for x in items]
    ex, pool_w = _get_executor(w)
    if ex is None:
        return [fn(x) for x in items]
    from concurrent.futures import CancelledError
    from concurrent.futures.process import BrokenProcessPool

    digest = hashlib.blake2b(fn_bytes, digest_size=16).digest()
    # ~2 chunks per worker (the pool's ACTUAL size — it is created once
    # per process and a later caller's `workers` cannot resize it):
    # smooths stragglers without multiplying the per-task fn_bytes
    # transfer
    chunk = max(1, -(-len(items) // (pool_w * 2)))
    chunks = [items[i : i + chunk] for i in range(0, len(items), chunk)]
    try:
        futures = [ex.submit(_run_task, digest, fn_bytes, c) for c in chunks]
        out: List = []
        for f in futures:
            out.extend(f.result())
        return out
    except (BrokenProcessPool, CancelledError, RuntimeError) as e:
        # infrastructure failure: a worker died, OR a concurrent caller
        # observed the same broken pool first and already shut it down
        # (submit then raises RuntimeError / pending futures cancel).
        # Either way this call completes sequentially and the dead pool
        # is torn down so the NEXT call builds a fresh one.  A
        # RuntimeError raised by fn ITSELF is a data error and must
        # propagate unchanged (sequential semantics).
        if (
            not isinstance(e, (BrokenProcessPool, CancelledError))
            # BrokenProcessPool IS a RuntimeError subclass — check it
            # first or the fallback below is unreachable for the exact
            # failure it exists for (a killed worker)
            and "schedule new futures" not in str(e)
        ):
            raise
        import logging

        logging.getLogger(__name__).warning(
            "host_map worker pool broke; completing this map "
            "sequentially and rebuilding the pool on next use"
        )
        shutdown()
        return [fn(x) for x in items]


# ---------------------------------------------------------- fleet host map

#: names that mean "this machine" — spawned directly, no ssh hop
LOCAL_HOSTS = frozenset({"local", "localhost", "127.0.0.1"})


class HostCapacityError(RuntimeError):
    """Every host in the map is at its slot budget.  A ``RuntimeError``
    — capacity exhaustion is an operator-visible limit, not transient
    infrastructure the retry ladder should absorb."""


class HostEntry:
    """One machine the fleet may spawn workers on: a host name and a
    slot budget (``None`` = unbounded)."""

    __slots__ = ("host", "slots", "spawned")

    def __init__(self, host: str, slots: Optional[int] = None):
        self.host = str(host)
        self.slots = None if slots is None else max(1, int(slots))
        self.spawned: list = []  # live subprocess.Popen handles

    @property
    def local(self) -> bool:
        return self.host in LOCAL_HOSTS

    def in_flight(self) -> int:
        self.spawned = [p for p in self.spawned if p.poll() is None]
        return len(self.spawned)

    def has_room(self) -> bool:
        return self.slots is None or self.in_flight() < self.slots


def parse_hosts(spec) -> List[HostEntry]:
    """The ``--hosts`` grammar: ``host[:slots]`` entries, comma
    separated — ``"local:2,10.0.0.5:4"`` — or an already-split list of
    entry strings / ``(host, slots)`` pairs.  A bare host has an
    unbounded slot budget."""
    if isinstance(spec, str):
        parts: Sequence = [p for p in spec.split(",") if p.strip()]
    else:
        parts = list(spec)
    entries: List[HostEntry] = []
    for part in parts:
        if isinstance(part, HostEntry):
            entries.append(part)
            continue
        if isinstance(part, (tuple, list)) and len(part) == 2:
            entries.append(HostEntry(part[0], part[1]))
            continue
        text = str(part).strip()
        host, _, slots = text.partition(":")
        if not host:
            raise ValueError(f"empty host in hosts spec {spec!r}")
        try:
            entries.append(HostEntry(host, int(slots) if slots else None))
        except ValueError:
            raise ValueError(
                f"bad slot count {slots!r} for host {host!r} "
                f"(want host[:slots])"
            ) from None
    if not entries:
        raise ValueError(f"hosts spec {spec!r} names no hosts")
    return entries


class HostMap:
    """The serving fleet's machine registry: where ``add_replica`` may
    spawn ``keystone worker --connect`` processes, and how many per
    host.  Local hosts spawn directly; remote hosts go through an ssh
    command template (overridable — site launchers vary).  The map only
    SPAWNS; registration happens when the worker dials the router's
    listener, so a worker started by hand (or by an operator on a host
    this map has never heard of) joins identically."""

    def __init__(
        self,
        hosts,
        python: Optional[str] = None,
        ssh_command: Optional[Sequence[str]] = None,
    ):
        import sys

        self.entries = parse_hosts(hosts)
        self.python = python or sys.executable
        #: the hop for non-local hosts; BatchMode so a missing key fails
        #: fast instead of prompting inside a serving control plane
        self.ssh_command = list(
            ssh_command
            if ssh_command is not None
            else ("ssh", "-o", "BatchMode=yes")
        )
        self._lock = threading.Lock()
        self._seq = 0

    def capacity(self) -> Optional[int]:
        """Total slot budget, or ``None`` when any host is unbounded —
        the autoscaler clamps its scale-up target to this."""
        total = 0
        for e in self.entries:
            if e.slots is None:
                return None
            total += e.slots
        return total

    def in_flight(self) -> int:
        with self._lock:
            return sum(e.in_flight() for e in self.entries)

    def _pick(self, allow_overflow: bool = False) -> HostEntry:
        """Least-loaded host with a free slot (ties break in map
        order, so the first-listed host fills first at equal load).

        ``allow_overflow``: when every budget is full, fall back to the
        least-loaded host anyway.  This is the blue/green swap's
        transient allowance — a staged generation COEXISTS with the old
        one it replaces until commit, so a slot budget sized to the
        steady-state fleet would otherwise fail every swap.  Steady
        consumers (the autoscaler, heals) keep the hard budget."""
        best: Optional[HostEntry] = None
        for e in self.entries:
            if not e.has_room():
                continue
            if best is None or e.in_flight() < best.in_flight():
                best = e
        if best is None and allow_overflow:
            best = min(self.entries, key=lambda e: e.in_flight())
            import logging

            logging.getLogger(__name__).info(
                "host slot budgets full; overflowing swap spawn onto %s "
                "(transient: the replaced generation retires at commit)",
                best.host,
            )
        if best is None:
            raise HostCapacityError(
                f"all {len(self.entries)} host(s) are at their slot "
                f"budget (capacity {self.capacity()})"
            )
        return best

    def _command(self, entry: HostEntry, args: List[str]) -> List[str]:
        local_cmd = [self.python, "-m", "keystone_tpu.cli", "worker"] + args
        if entry.local:
            return local_cmd
        return self.ssh_command + [entry.host] + local_cmd

    def spawn(
        self,
        connect_address: str,
        worker_name: Optional[str] = None,
        extra_args: Sequence[str] = (),
        allow_overflow: bool = False,
    ):
        """Start one ``keystone worker`` pointed at the router's
        listener; returns the ``subprocess.Popen``.  The child inherits
        this environment (so ``KEYSTONE_FAULTS`` plans and platform
        pins propagate exactly as they do to pipe-spawned workers).
        ``allow_overflow`` exempts this spawn from the slot budget —
        the swap path's transient allowance (see :meth:`_pick`)."""
        import subprocess

        with self._lock:
            entry = self._pick(allow_overflow=allow_overflow)
            self._seq += 1
            name = worker_name or f"{entry.host}-w{self._seq}"
            args = ["--connect", str(connect_address), "--name", name]
            args.extend(extra_args)
            cmd = self._command(entry, args)
            proc = subprocess.Popen(cmd, env=dict(os.environ))
            entry.spawned.append(proc)
        import logging

        logging.getLogger(__name__).info(
            "spawned worker %s on %s (pid %d)", name, entry.host, proc.pid
        )
        return proc

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity(),
                "in_flight": sum(e.in_flight() for e in self.entries),
                "hosts": [
                    {
                        "host": e.host,
                        "slots": e.slots,
                        "in_flight": e.in_flight(),
                    }
                    for e in self.entries
                ],
            }

    def close(self, timeout: float = 3.0) -> None:
        """Reap every spawned worker: terminate, short grace, kill.
        Workers also exit on their own when the router's listener goes
        away (their reconnect budget runs dry), but a closing pool must
        not leave children to that slow path."""
        with self._lock:
            procs = [p for e in self.entries for p in e.spawned]
            for e in self.entries:
                e.spawned = []
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + max(0.2, timeout)
        for p in procs:
            remain = deadline - time.monotonic()
            try:
                p.wait(max(0.05, remain))
            except Exception:
                try:
                    p.kill()
                    p.wait(1.0)
                except Exception:
                    pass
