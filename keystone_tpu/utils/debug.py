"""Numeric debugging helpers.

The reference relies on JVM memory safety and has no sanitizers
(SURVEY.md §5 "Race detection"); the TPU-era equivalents are jit purity
plus checkify/debug assertions for NaN and out-of-bounds detection —
wrapped here so solvers/pipelines can opt in without touching jax APIs.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import checkify


def assert_all_finite(x, name: str = "array"):
    """Host-side finiteness check for eager pipeline outputs."""
    import numpy as np

    arr = np.asarray(x)
    if not np.isfinite(arr).all():
        bad = int((~np.isfinite(arr)).sum())
        raise FloatingPointError(f"{name}: {bad} non-finite values")
    return x


def checked(fn: Callable) -> Callable:
    """Wrap a jittable fn with checkify NaN/div checks; raises on error.

    Usage: ``checked(solver_fn)(args)`` — compiles once, errors surface as
    ``jax.experimental.checkify.JaxRuntimeError`` with location info.
    """
    checked_fn = checkify.checkify(
        fn, errors=checkify.float_checks | checkify.index_checks
    )

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        err, out = checked_fn(*args, **kwargs)
        err.throw()
        return out

    return wrapper


def nan_guard_dataset(ds, name: str = "dataset"):
    """Eagerly validate a Dataset's array (skips host payloads)."""
    if not ds.is_host:
        assert_all_finite(ds.numpy(), name)
    return ds
