"""Profiler tracing + per-stage timing.

The reference has no dedicated tracer: it relies on (1) the optimizer's
sampling-based node profiling (AutoCacheRule) and (2) Spark's event-log
UI timeline, with apps logging coarse stage timings via the Logging trait
(SURVEY.md §5 "Tracing/profiling").  The TPU-era equivalents here:

- ``trace(logdir)`` / ``start_trace``/``stop_trace``: wrap
  ``jax.profiler`` to capture a device trace viewable in
  TensorBoard/Perfetto — the Spark-UI-timeline replacement.
- ``annotate(name)``: a named region (``jax.profiler.TraceAnnotation``)
  so pipeline stages show up by name inside the trace.
- ``stage_timings(result)``: coarse per-node wall timings of a lazy
  pipeline result (the Logging-trait stage-timings replacement), using
  the executor's profiling mode (device-synchronized per node).

The HLO-cost-model side of profiling (the AutoCacheRule analogue proper)
lives in ``workflow/profiling.py``.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax


def start_trace(logdir: str) -> None:
    """Begin capturing a jax.profiler device trace into ``logdir``."""
    jax.profiler.start_trace(logdir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str, annotation: Optional[str] = None):
    """Capture a device trace around a block::

        with tracing.trace("/tmp/keystone-trace"):
            pipeline.fit()

    View with TensorBoard (tensorboard-plugin-profile) or Perfetto.
    """
    with jax.profiler.trace(logdir):
        if annotation is None:
            yield
        else:
            with jax.profiler.TraceAnnotation(annotation):
                yield


def annotate(name: str):
    """Named region inside an active trace (stages show by name)."""
    return jax.profiler.TraceAnnotation(name)


def step_annotation(step: int, name: str = "step"):
    """Mark one solver/pipeline iteration (StepTraceAnnotation)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def stage_timings(result) -> Dict[str, float]:
    """Per-node wall seconds for a lazy pipeline result.

    Runs the pipeline optimizer first (same as ``result.get()``), then
    executes the optimized graph in the executor's profiling mode (each
    node's output is device-synchronized before the clock stops, so times
    are real compute, not dispatch) — so the nodes reported are the ones
    that actually run, including optimizer-fused/inserted stages.  Keys
    are ``"{node_id}:{label}"`` — the node id disambiguates repeated ops.
    """
    from keystone_tpu.workflow.executor import GraphExecutor
    from keystone_tpu.workflow.pipeline import PipelineEnv

    g = PipelineEnv.get_optimizer().execute(result.graph)
    ex = GraphExecutor(g, profile=True)
    ex.execute(result.sink)
    out: Dict[str, float] = {}
    for node, seconds in ex.timings.items():
        label = g.operators[node].label() if node in g.operators else str(node)
        out[f"{node.id}:{label}"] = seconds
    return out
