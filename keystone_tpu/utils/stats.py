"""Random matrices and numeric test helpers (utils/Stats.scala)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def about_eq(a, b, thresh: float = 1e-8) -> bool:
    """Elementwise |a-b| <= thresh, reduced with all() (Stats.aboutEq)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.shape != b.shape:
        return False
    return bool(np.all(np.abs(a - b) <= thresh))


def rand_matrix_gaussian(key, rows: int, cols: int, dtype=jnp.float32):
    return jax.random.normal(key, (rows, cols), dtype=dtype)


def rand_matrix_uniform(key, rows: int, cols: int, dtype=jnp.float32):
    return jax.random.uniform(key, (rows, cols), dtype=dtype)


def rand_matrix_cauchy(key, rows: int, cols: int, dtype=jnp.float32):
    """Standard Cauchy draws (used by CosineRandomFeatures' Laplacian kernel
    variant, nodes/stats/CosineRandomFeatures.scala)."""
    return jax.random.cauchy(key, (rows, cols), dtype=dtype)
