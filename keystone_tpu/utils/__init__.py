from keystone_tpu.utils.image import (  # noqa: F401
    Image,
    ImageMetadata,
    image_from_array,
)
from keystone_tpu.utils.matrix import (  # noqa: F401
    rows_to_matrix,
    matrix_to_rows,
    shuffle_rows,
)
from keystone_tpu.utils.stats import (  # noqa: F401
    about_eq,
    rand_matrix_cauchy,
    rand_matrix_gaussian,
    rand_matrix_uniform,
)
from keystone_tpu.utils import tracing  # noqa: F401
from keystone_tpu.utils import durable  # noqa: F401
from keystone_tpu.utils.durable import CorruptStateError  # noqa: F401
from keystone_tpu.utils import guard  # noqa: F401
from keystone_tpu.utils.guard import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    Heartbeat,
    run_with_deadline,
)

# Test-fixture generators (the reference's src/test/scala/utils/TestUtils
# analogue) live in keystone_tpu.utils.test_utils — import that module
# directly from test code; they are deliberately NOT re-exported here.
