"""Test fixture generators.

Reference: src/test/scala/utils/TestUtils.scala — helpers used across the
suites (``genChannelMajorArrayVectorizedImage`` random images,
``loadTestImage`` resource images).  The reference ships tiny binary
fixtures in test resources; this repo carries none, so ``load_test_image``
returns deterministic *procedural* images (gradient / checkerboard /
blobs) that play the same role: small, known content, stable across runs.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu.utils.image import Image, image_from_array


def gen_image(
    height: int = 16, width: int = 16, channels: int = 3, seed: int = 0
) -> Image:
    """Random image with values in [0, 1) — the
    genChannelMajorArrayVectorizedImage analogue (layout is XLA's concern;
    data is (H, W, C))."""
    rng = np.random.default_rng(seed)
    return image_from_array(
        rng.uniform(size=(height, width, channels)).astype(np.float32)
    )


def gen_image_batch(
    n: int = 4, height: int = 16, width: int = 16, channels: int = 3, seed: int = 0
) -> np.ndarray:
    """(N, H, W, C) float32 batch of random images."""
    rng = np.random.default_rng(seed)
    return rng.uniform(size=(n, height, width, channels)).astype(np.float32)


def load_test_image(name: str = "gradient", size: int = 32) -> Image:
    """Deterministic known-content test image (loadTestImage analogue).

    ``gradient``     — channel 0 ramps along x, channel 1 along y,
                       channel 2 radial.
    ``checkerboard`` — 4-pixel checker tiles, all channels equal.
    ``blobs``        — two Gaussian bumps (distinct scales/positions);
                       useful for keypoint/descriptor ops.
    """
    x = np.linspace(0.0, 1.0, size, dtype=np.float32)
    xx, yy = np.meshgrid(x, x, indexing="ij")
    if name == "gradient":
        r = np.sqrt((xx - 0.5) ** 2 + (yy - 0.5) ** 2) / np.sqrt(0.5)
        img = np.stack([xx, yy, r.astype(np.float32)], axis=-1)
    elif name == "checkerboard":
        tile = ((xx * size // 4).astype(int) + (yy * size // 4).astype(int)) % 2
        img = np.repeat(tile[:, :, None].astype(np.float32), 3, axis=-1)
    elif name == "blobs":
        b1 = np.exp(-(((xx - 0.3) ** 2 + (yy - 0.3) ** 2) / 0.02))
        b2 = np.exp(-(((xx - 0.7) ** 2 + (yy - 0.65) ** 2) / 0.08))
        g = (b1 + 0.6 * b2).astype(np.float32)
        img = np.stack([g, g, g], axis=-1)
    else:
        raise ValueError(
            f"unknown test image {name!r}: gradient | checkerboard | blobs"
        )
    return image_from_array(img)
