"""Image representation.

The reference carries three flat-``Array[Double]`` image classes with
different memory orders, chosen per-op for cache locality
(utils/Image.scala § ChannelMajorArrayVectorizedImage,
ColumnMajorArrayVectorizedImage, RowMajorArrayVectorizedImage).  On TPU
the memory-order menagerie is pointless: XLA owns layout.  An image is a
dense ``(H, W, C)`` float array (NHWC when batched, the TPU-friendly conv
layout), and `Image` is a thin metadata-carrying wrapper used at pipeline
boundaries; all compute ops take/return bare arrays.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ImageMetadata:
    """Dimensions record (utils/Image.scala § ImageMetadata)."""

    x_dim: int  # height
    y_dim: int  # width
    num_channels: int

    @property
    def shape(self):
        return (self.x_dim, self.y_dim, self.num_channels)


@dataclasses.dataclass(frozen=True)
class Image:
    """An (H, W, C) image; ``data`` is a jnp/np array."""

    data: jnp.ndarray

    @property
    def metadata(self) -> ImageMetadata:
        h, w, c = self.data.shape
        return ImageMetadata(h, w, c)

    def get(self, x: int, y: int, c: int):
        return self.data[x, y, c]

    def to_vector(self) -> jnp.ndarray:
        return self.data.reshape(-1)


def image_from_array(arr) -> Image:
    arr = jnp.asarray(arr)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.ndim != 3:
        raise ValueError(f"expected (H,W[,C]) array, got shape {arr.shape}")
    return Image(arr)


def grayscale(images: jnp.ndarray) -> jnp.ndarray:
    """Luminance conversion for batched NHWC images (utils/ImageUtils.scala).

    Uses the same equal-weight channel mean the reference's GrayScaler
    applies (it averages channels rather than using Rec.601 weights).
    """
    if images.shape[-1] == 1:
        return images[..., 0]
    return jnp.mean(images, axis=-1)


def to_numpy(img) -> np.ndarray:
    if isinstance(img, Image):
        return np.asarray(img.data)
    return np.asarray(img)


# ---- batched image utilities (utils/ImageUtils.scala) ----
def crop(images: jnp.ndarray, y0: int, x0: int, h: int, w: int) -> jnp.ndarray:
    """Crop batched NHWC (or HWC) images."""
    if images.ndim == 3:
        return images[y0 : y0 + h, x0 : x0 + w, :]
    return images[:, y0 : y0 + h, x0 : x0 + w, :]


def flip_horizontal(images: jnp.ndarray) -> jnp.ndarray:
    return images[..., :, ::-1, :] if images.ndim >= 3 else images[:, ::-1]


def flip_vertical(images: jnp.ndarray) -> jnp.ndarray:
    return images[..., ::-1, :, :] if images.ndim >= 3 else images[::-1, :]


def map_pixels(images: jnp.ndarray, fn) -> jnp.ndarray:
    """Elementwise pixel transform (ImageUtils.mapPixels)."""
    return fn(images)


def pixel_stats(images: jnp.ndarray):
    """(mean, std) over the batch per channel."""
    axes = tuple(range(images.ndim - 1))
    return jnp.mean(images, axis=axes), jnp.std(images, axis=axes)
