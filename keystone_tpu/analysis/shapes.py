"""Pass (a): abstract shape/dtype interpretation of the workflow graph.

The reference rejects a mis-wired ``Transformer`` chain at compile time;
here the same walk runs ahead of fit with *abstract* values: every
dataset literal (and the open source, when the caller supplies an
example) becomes a ``jax.ShapeDtypeStruct``, and each device transformer
is pushed through ``jax.eval_shape`` over its ``apply_batch`` — the
exact callable the runtime jits — so stage-to-stage incompatibilities
surface as findings *before any device work*, not minutes into an
expensive fit.

Abstract value lattice (per graph id):

- :class:`ArrayVal` — a device batch: ShapeDtypeStruct (+ optional
  ragged mask aval), mirroring ``Dataset.array`` / ``Dataset.mask``;
- :class:`HostVal`  — a host payload (text, term dicts); ``stream=True``
  marks a host StreamDataset, whose device-transformer consumers raise
  at runtime (``Transformer.apply_dataset``) and error here;
- :class:`FittedVal` — the output of an estimator node (opaque: the
  fitted transformer's output shape is a property of training);
- :data:`UNKNOWN`  — propagation gave up (host maps, opaque fitted
  applies); nothing downstream of an UNKNOWN is reported, so giving up
  is silent, never a false positive.

Findings:

- ``shape-mismatch`` (error): ``eval_shape`` failed with a shape/dtype/
  rank complaint — the stage cannot accept what its predecessor emits;
- ``not-unary`` / ``bad-delegate`` / ``missing-labels`` /
  ``unfitted-estimator`` / ``gather-host`` / ``gather-mismatch``
  (errors): structural mis-wirings the executor would only hit at run
  time;
- ``dtype-downcast`` (warning): a literal/source carries f64 (or i64)
  data that jax silently narrows under the default x64-disabled config;
- ``stage-downcast`` (warning): a stage emits a lower-precision float
  than it consumes (f64→f32, f32→bf16) — the silent-coercion class the
  PR-2 byte-identity pins only covered on two paths;
- ``gather-promotion`` (warning): gather branches disagree on dtype, so
  the concat silently promotes.

Untraceable stages (host-side numpy, data-dependent Python — the same
population ``_apply_batch_jitted`` memoizes as untraceable at runtime)
degrade to UNKNOWN with a debug log, not a finding: the analyzer's
false-positive gate (zero findings over every bundled pipeline) is part
of its contract.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional

from keystone_tpu.analysis.findings import PASS_SHAPES, Finding
from keystone_tpu.workflow import graph as G

logger = logging.getLogger(__name__)


# ------------------------------------------------------- abstract values
class _Abstract:
    pass


@dataclasses.dataclass(frozen=True)
class ArrayVal(_Abstract):
    aval: object  # jax.ShapeDtypeStruct
    mask: Optional[object] = None  # ShapeDtypeStruct of the ragged mask


@dataclasses.dataclass(frozen=True)
class HostVal(_Abstract):
    stream: bool = False


@dataclasses.dataclass(frozen=True)
class FittedVal(_Abstract):
    label: str = ""


class _Unknown(_Abstract):
    def __repr__(self):
        return "UNKNOWN"


UNKNOWN = _Unknown()

#: substrings classifying an eval_shape failure as a genuine wiring
#: error rather than mere untraceability (jax shape errors are
#: TypeError/ValueError mentioning one of these)
_SHAPE_ERROR_MARKERS = (
    "shape",
    "dimension",
    "rank",
    "dtype",
    "incompatible",
    "broadcast",
    "concatenate",
    "dot_general",
    "size",
    "ndim",
)

_WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")


def source_abstract(example) -> _Abstract:
    """Abstract value for the pipeline's open source from a caller
    example: a Dataset, a batch-like array, a ``jax.ShapeDtypeStruct``,
    or a per-item shape tuple (a synthetic f32 batch is assumed)."""
    import jax
    import numpy as np

    from keystone_tpu.workflow.dataset import Dataset

    if example is None:
        return UNKNOWN
    if isinstance(example, _Abstract):
        return example
    if isinstance(example, Dataset):
        return _dataset_abstract(example, [])
    if isinstance(example, jax.ShapeDtypeStruct):
        return ArrayVal(example)
    if isinstance(example, tuple) and all(isinstance(d, int) for d in example):
        return ArrayVal(jax.ShapeDtypeStruct((4,) + example, np.float32))
    if hasattr(example, "shape") and hasattr(example, "dtype"):
        return ArrayVal(
            jax.ShapeDtypeStruct(tuple(example.shape), example.dtype)
        )
    if isinstance(example, (list,)):  # host payload example (texts)
        return HostVal()
    return UNKNOWN


def _dataset_abstract(ds, findings: List[Finding], node=None, label=None):
    """Abstract value of a bound dataset literal.  Streams are peeked
    (one batch of host work — the price of validating an out-of-core
    pipeline); failures degrade to UNKNOWN."""
    import jax

    from keystone_tpu.workflow.dataset import StreamDataset

    if isinstance(ds, StreamDataset):
        if ds.is_host:
            return HostVal(stream=True)
        try:
            for arr, mask in ds.device_batches():
                aval = jax.ShapeDtypeStruct(tuple(arr.shape), arr.dtype)
                mval = (
                    None
                    if mask is None
                    else jax.ShapeDtypeStruct(tuple(mask.shape), mask.dtype)
                )
                _check_wide(aval, findings, node, label)
                return ArrayVal(aval, mval)
            return UNKNOWN  # empty stream: nothing to propagate
        except Exception as e:
            logger.debug("stream peek failed for %s: %s", label, e)
            return UNKNOWN
    if ds.is_host:
        return HostVal()
    arr = ds.array
    aval = jax.ShapeDtypeStruct(tuple(arr.shape), arr.dtype)
    mval = (
        None
        if ds.mask is None
        else jax.ShapeDtypeStruct(tuple(ds.mask.shape), ds.mask.dtype)
    )
    _check_wide(aval, findings, node, label)
    return ArrayVal(aval, mval)


def _check_wide(aval, findings: List[Finding], node, label) -> None:
    if str(aval.dtype) in _WIDE_DTYPES:
        findings.append(
            Finding(
                "warning",
                PASS_SHAPES,
                "dtype-downcast",
                f"input carries {aval.dtype} data; jax (x64 disabled) "
                "silently narrows it to 32-bit on device — cast "
                "explicitly if the narrowing is intended",
                node=None if node is None else node.id,
                label=label,
            )
        )


def _is_float(dtype) -> bool:
    import numpy as np

    return np.issubdtype(np.dtype(str(dtype)), np.floating) or "bfloat16" in str(
        dtype
    )


_FLOAT_ORDER = {"bfloat16": 16, "float16": 16, "float32": 32, "float64": 64}


def _apply_transformer_abstract(
    t, val: _Abstract, node, findings: List[Finding]
) -> _Abstract:
    """Push one transformer over an abstract input, mirroring
    ``Transformer.apply_dataset``'s dispatch."""
    import jax

    from keystone_tpu.workflow.transformer import Cacher

    label = t.label
    if isinstance(t, Cacher):  # materialization barrier: identity
        return val
    if isinstance(val, _Unknown):
        return UNKNOWN
    if isinstance(val, FittedVal):
        findings.append(
            Finding(
                "error",
                PASS_SHAPES,
                "bad-wiring",
                f"{label} is applied to a fitted-transformer value; "
                "transformers consume datasets",
                node=node.id,
                label=label,
            )
        )
        return UNKNOWN
    if t.is_host:
        # host transformer: maps apply_one over items; output shape is a
        # property of the host code — propagate an opaque host value
        return HostVal(stream=isinstance(val, HostVal) and val.stream)
    if isinstance(val, HostVal):
        if val.stream:
            # Transformer.apply_dataset raises exactly this at runtime
            findings.append(
                Finding(
                    "error",
                    PASS_SHAPES,
                    "host-stream-device-stage",
                    f"{label} is a device transformer but its input is a "
                    "host-payload stream; featurize to arrays first",
                    node=node.id,
                    label=label,
                )
            )
            return UNKNOWN
        return UNKNOWN  # in-memory host items: applied per item, shape opaque
    # kernel-tier mappers get an EXPLICIT case before the generic walk:
    # the out-of-core mapper's apply streams train blocks from DISK, so
    # pushing it through eval_shape would do real IO mid-analysis, and
    # a misshaped kernel state (α rows vs train rows) only explodes
    # mid-sweep at runtime — both must fail pre-flight instead
    kernel_out = _kernel_case(t, val, node, findings)
    if kernel_out is not None:
        return kernel_out
    # device transformer over a device batch: the real eval_shape walk
    assert isinstance(val, ArrayVal)
    try:
        if val.mask is not None:
            out = jax.eval_shape(
                lambda a, m: t.apply_batch(a, mask=m), val.aval, val.mask
            )
        else:
            out = jax.eval_shape(lambda a: t.apply_batch(a), val.aval)
    except Exception as e:
        msg = str(e)
        low = msg.lower()
        # tracer/concretization errors (data-dependent Python, host
        # numpy on tracers) are UNTRACEABILITY, not wiring errors — the
        # runtime executes those stages on the unjitted fallback, and
        # their messages mention tracer shapes, so they must be
        # excluded BEFORE the marker heuristic or a working pipeline
        # gets refused (zero-false-positive contract)
        if isinstance(e, jax.errors.JAXTypeError):
            logger.debug("stage %s is unanalyzable (tracer): %s", label, e)
            return UNKNOWN
        if isinstance(e, (TypeError, ValueError)) and any(
            k in low for k in _SHAPE_ERROR_MARKERS
        ):
            findings.append(
                Finding(
                    "error",
                    PASS_SHAPES,
                    "shape-mismatch",
                    f"{label} cannot accept input "
                    f"{tuple(val.aval.shape)}:{val.aval.dtype}: "
                    + msg.splitlines()[0][:300],
                    node=node.id,
                    label=label,
                )
            )
        else:
            # untraceable (host numpy, data-dependent python) — the same
            # population the runtime jit wrapper falls back on; not a
            # wiring error, so not a finding
            logger.debug("stage %s is unanalyzable: %s", label, e)
        return UNKNOWN
    if isinstance(out, tuple) and len(out) == 2:
        out_arr, out_mask = out
        result = ArrayVal(out_arr, out_mask)
    else:
        out_arr = out
        result = ArrayVal(out_arr)  # with_array drops the mask
    in_dt, out_dt = str(val.aval.dtype), str(out_arr.dtype)
    if (
        _is_float(in_dt)
        and _is_float(out_dt)
        and _FLOAT_ORDER.get(out_dt, 32) < _FLOAT_ORDER.get(in_dt, 32)
    ):
        findings.append(
            Finding(
                "warning",
                PASS_SHAPES,
                "stage-downcast",
                f"{label} narrows {in_dt} input to {out_dt} output — "
                "silent precision loss unless the stage is under an "
                "explicit precision policy",
                node=node.id,
                label=label,
            )
        )
    return result


def check_kernel_generator(kg, findings: List[Finding], node, label) -> bool:
    """Validate a Gaussian-kernel generator's γ: non-finite or
    non-positive γ makes the whole kernel degenerate (exp(0)=1
    everywhere) and the sweep converges to garbage SILENTLY.  Returns
    True when a finding was emitted."""
    import math

    gamma = getattr(kg, "gamma", None)
    if gamma is None:
        return False
    try:
        g = float(gamma)
    except (TypeError, ValueError):
        g = float("nan")
    if not math.isfinite(g) or g <= 0.0:
        findings.append(
            Finding(
                "error",
                PASS_SHAPES,
                "bad-kernel-generator",
                f"{label} carries a GaussianKernelGenerator with "
                f"gamma={gamma!r}; γ must be a finite positive scalar "
                "or every kernel value degenerates to exp(0)=1",
                node=None if node is None else node.id,
                label=label,
            )
        )
        return True
    return False


def _kernel_case(
    t, val: _Abstract, node, findings: List[Finding]
) -> Optional[_Abstract]:
    """Explicit shapes case for the kernel tier's fitted mappers
    (KernelBlockLinearMapper / OutOfCoreKernelBlockLinearMapper /
    NystromFeatureMap): returns None when ``t`` is none of them (the
    generic eval_shape walk proceeds), else an abstract output after
    checking the kernel-specific invariants the generic walk cannot —
    fitted-state consistency, the disk-backed store's feature dim, and
    generator validity."""
    import jax
    import numpy as np

    try:
        from keystone_tpu.models.kernel_ridge import (
            KernelBlockLinearMapper,
            OutOfCoreKernelBlockLinearMapper,
        )
        from keystone_tpu.models.nystrom import NystromFeatureMap
    except Exception:  # pragma: no cover - models always importable here
        return None

    if not isinstance(
        t,
        (
            KernelBlockLinearMapper,
            OutOfCoreKernelBlockLinearMapper,
            NystromFeatureMap,
        ),
    ):
        return None
    assert isinstance(val, ArrayVal)
    label = t.label
    bad = check_kernel_generator(t.kernel_gen, findings, node, label)
    d_in = int(val.aval.shape[-1]) if len(val.aval.shape) else None

    def _mismatch(train_d, what):
        findings.append(
            Finding(
                "error",
                PASS_SHAPES,
                "kernel-shape-mismatch",
                f"{label} computes kernels against {what} with "
                f"{train_d} features but its input carries {d_in}",
                node=node.id,
                label=label,
            )
        )

    if isinstance(t, NystromFeatureMap):
        m, train_d = (int(s) for s in t.landmarks.shape)
        if d_in is not None and d_in != train_d:
            _mismatch(train_d, "landmarks")
            return UNKNOWN
        if tuple(int(s) for s in t.whiten.shape) != (m, m):
            findings.append(
                Finding(
                    "error",
                    PASS_SHAPES,
                    "kernel-bad-state",
                    f"{label} whitening is {tuple(t.whiten.shape)} for "
                    f"{m} landmarks; the fitted state is inconsistent",
                    node=node.id,
                    label=label,
                )
            )
            return UNKNOWN
        if bad:
            return UNKNOWN
        return ArrayVal(
            jax.ShapeDtypeStruct(val.aval.shape[:-1] + (m,), np.float32)
        )

    if isinstance(t, KernelBlockLinearMapper):
        rows, train_d = (int(s) for s in t.train_x.shape)
        alpha_rows, k = (int(s) for s in t.alpha.shape)
    else:  # out-of-core: read the store's META only — never its blocks
        try:
            st = t._store()
            rows, train_d = st.num_blocks * st.block_size, st.d
        except Exception as e:
            findings.append(
                Finding(
                    "error",
                    PASS_SHAPES,
                    "kernel-bad-state",
                    f"{label} cannot open its backing row-block store "
                    f"({t.store_directory}): {e} — the store is part of "
                    "the fitted model and must outlive it",
                    node=node.id,
                    label=label,
                )
            )
            return UNKNOWN
        alpha_rows, k = (int(s) for s in t.alpha.shape)
    if alpha_rows != rows:
        findings.append(
            Finding(
                "error",
                PASS_SHAPES,
                "kernel-bad-state",
                f"{label} holds α with {alpha_rows} rows against "
                f"{rows} train rows; the fitted state is inconsistent",
                node=node.id,
                label=label,
            )
        )
        return UNKNOWN
    if d_in is not None and d_in != train_d:
        _mismatch(train_d, "the train rows")
        return UNKNOWN
    if bad:
        return UNKNOWN
    return ArrayVal(
        jax.ShapeDtypeStruct(val.aval.shape[:-1] + (k,), np.float32)
    )


def _gather_abstract(vals, node, findings: List[Finding]) -> _Abstract:
    import jax
    import numpy as np

    if any(isinstance(v, _Unknown) for v in vals):
        return UNKNOWN
    if any(isinstance(v, (HostVal, FittedVal)) for v in vals):
        findings.append(
            Finding(
                "error",
                PASS_SHAPES,
                "gather-host",
                "gather requires device-array branches; a branch "
                "produces a host (or fitted-transformer) payload",
                node=node.id,
                label="Gather",
            )
        )
        return UNKNOWN
    shapes = [tuple(v.aval.shape) for v in vals]
    ranks = {len(s) for s in shapes}
    leads = {s[:-1] for s in shapes}
    if len(ranks) > 1 or len(leads) > 1:
        findings.append(
            Finding(
                "error",
                PASS_SHAPES,
                "gather-mismatch",
                f"gather branches disagree on shape outside the feature "
                f"axis: {sorted(set(shapes))}",
                node=node.id,
                label="Gather",
            )
        )
        return UNKNOWN
    dtypes = {str(v.aval.dtype) for v in vals}
    if len(dtypes) > 1:
        findings.append(
            Finding(
                "warning",
                PASS_SHAPES,
                "gather-promotion",
                f"gather branches disagree on dtype {sorted(dtypes)}; "
                "the concat silently promotes",
                node=node.id,
                label="Gather",
            )
        )
    shape = shapes[0][:-1] + (sum(s[-1] for s in shapes),)
    dt = np.result_type(*[np.dtype(d) if d != "bfloat16" else np.float32 for d in dtypes])
    return ArrayVal(jax.ShapeDtypeStruct(shape, dt))


def run(
    graph: G.Graph,
    sources: Optional[Dict[G.SourceId, _Abstract]] = None,
    mode: str = "fit",
) -> List[Finding]:
    """Walk ``graph`` with abstract values.  ``sources`` seeds open
    sources (unseeded sources propagate UNKNOWN).  ``mode="apply"``
    additionally errors on any remaining EstimatorOperator — the freeze/
    serve contract (an unfitted pipeline cannot be applied)."""
    from keystone_tpu.workflow.dataset import as_dataset
    from keystone_tpu.workflow.estimator import LabelEstimator

    findings: List[Finding] = []
    values: Dict[object, _Abstract] = {}
    for s in graph.sources:
        v = (sources or {}).get(s, UNKNOWN)
        values[s] = v
        if isinstance(v, ArrayVal):
            _check_wide(v.aval, findings, None, f"source {s.id}")

    for n in graph.topological_nodes():
        op = graph.operators[n]
        deps = graph.dependencies[n]
        dvals = [values.get(d, UNKNOWN) for d in deps]
        out: _Abstract = UNKNOWN
        if isinstance(op, G.DatasetOperator):
            try:
                ds = as_dataset(op.dataset)
                out = _dataset_abstract(ds, findings, node=n, label=op.label())
            except Exception as e:
                logger.debug("dataset literal unanalyzable at %s: %s", n, e)
        elif isinstance(op, G.DatumOperator):
            datum = op.datum
            if hasattr(datum, "shape") and hasattr(datum, "dtype"):
                import jax

                aval = jax.ShapeDtypeStruct(
                    (1,) + tuple(datum.shape), datum.dtype
                )
                _check_wide(aval, findings, n, op.label())
                out = ArrayVal(aval)
            else:
                out = HostVal()
        elif isinstance(op, G.TransformerOperator):
            if len(deps) != 1:
                findings.append(
                    Finding(
                        "error",
                        PASS_SHAPES,
                        "not-unary",
                        f"{op.label()} has {len(deps)} dependencies; "
                        "transformers are unary",
                        node=n.id,
                        label=op.label(),
                    )
                )
            else:
                out = _apply_transformer_abstract(
                    op.transformer, dvals[0], n, findings
                )
        elif isinstance(op, G.EstimatorOperator):
            # kernel estimators (KernelRidgeRegression, NystromFeatures)
            # carry their generator pre-fit: a degenerate γ must fail
            # HERE, not after an epoch of wasted sweeps
            kg = getattr(op.estimator, "kernel_gen", None)
            if kg is not None:
                check_kernel_generator(kg, findings, n, op.label())
            if mode == "apply":
                findings.append(
                    Finding(
                        "error",
                        PASS_SHAPES,
                        "unfitted-estimator",
                        f"{op.label()} is unfitted; fit() the pipeline "
                        "before freezing/applying it",
                        node=n.id,
                        label=op.label(),
                    )
                )
            if isinstance(op.estimator, LabelEstimator) and len(deps) < 2:
                findings.append(
                    Finding(
                        "error",
                        PASS_SHAPES,
                        "missing-labels",
                        f"{op.label()} is a LabelEstimator but its node "
                        "has no labels dependency",
                        node=n.id,
                        label=op.label(),
                    )
                )
            if dvals and isinstance(dvals[0], FittedVal):
                findings.append(
                    Finding(
                        "error",
                        PASS_SHAPES,
                        "bad-wiring",
                        f"{op.label()} consumes a fitted-transformer "
                        "value; estimators fit on datasets",
                        node=n.id,
                        label=op.label(),
                    )
                )
            out = FittedVal(label=op.label())
        elif isinstance(op, G.DelegatingOperator):
            if not dvals or not isinstance(dvals[0], FittedVal):
                # dep 0 must be the estimator's output — anything else is
                # the unfitted-estimator-reference class (the executor
                # raises TypeError at run time, possibly hours in)
                if dvals and isinstance(dvals[0], _Unknown):
                    out = UNKNOWN
                else:
                    findings.append(
                        Finding(
                            "error",
                            PASS_SHAPES,
                            "bad-delegate",
                            "delegating apply expects a fitted transformer "
                            "as dependency 0 (unfitted-estimator "
                            "reference?)",
                            node=n.id,
                            label=op.label(),
                        )
                    )
            else:
                out = UNKNOWN  # fitted output shape is a training property
        elif isinstance(op, G.GatherOperator):
            out = _gather_abstract(dvals, n, findings)
        values[n] = out
    return findings
