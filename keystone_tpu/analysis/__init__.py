"""Pre-flight static analysis for workflow pipelines.

The reference's headline property — statically type-safe pipelines —
rebuilt for the jax port as an ahead-of-fit analyzer: abstract
shape/dtype interpretation over the workflow graph (``jax.eval_shape``,
no device work), a precision-policy lint over solver jaxprs, a
robustness-configuration lint (fault plans, breakers, deadlines), and a
CSE/cache-signature collision audit.  Typed findings; wired into
``Pipeline.fit(validate=)`` / ``KEYSTONE_VALIDATE=1``,
``Pipeline.freeze()``, and ``python -m keystone_tpu.cli check``.

The repo-invariant AST linter (fault-site registration, metric naming,
monotonic clocks under guard supervision, obs-hook gating) lives in
``tools/lint.py`` and is enforced as a tier-1 test.
"""

from keystone_tpu.analysis.analyzer import (  # noqa: F401
    ALL_PASSES,
    DEFAULT_PASSES,
    ENV_VALIDATE,
    analyze,
    validate_fit,
    validate_freeze,
    validation_enabled,
)
from keystone_tpu.analysis.bundled import BUNDLED, build_bundled  # noqa: F401
from keystone_tpu.analysis.findings import (  # noqa: F401
    AnalysisReport,
    Finding,
    PipelineValidationError,
)
from keystone_tpu.analysis.precision import (  # noqa: F401
    MODES,
    SOLVER_ENTRIES,
    check_fn,
)
