"""Pre-flight pipeline analyzer: orchestration and wiring helpers.

:func:`analyze` walks a pipeline's graph with the requested passes and
returns an :class:`~keystone_tpu.analysis.findings.AnalysisReport`:

- ``shapes``     — abstract shape/dtype interpretation (pass a);
- ``robustness`` — fault-plan / breaker / deadline configuration (c);
- ``signatures`` — CSE / cache-signature collision audit (d);
- ``precision``  — solver-jaxpr precision lint (b; graph-independent
  and the only pass that traces solver code, so it is NOT in the
  default set — ``cli.py check`` adds it);
- ``plan``       — installed physical-plan audit (stale-plan /
  bad-plan-candidate; inert with no plan installed, so it rides
  ``validate_freeze`` and ``cli.py check`` but not the fit default
  set).

Entry points used by the framework wiring:

- ``Pipeline.fit(validate=…)`` / ``KEYSTONE_VALIDATE=1`` →
  :func:`validate_fit` (cheap default passes; raises
  :class:`PipelineValidationError` on errors, logs warnings);
- ``Pipeline.freeze(validate=…)`` → :func:`validate_freeze`
  (``mode="apply"``: unfitted estimators are errors);
- ``python -m keystone_tpu.cli check`` → :func:`analyze` with every
  pass plus DOT overlay output.

With validation off (the default) none of this module is imported by
the fit/freeze paths — the inert-path guarantee the solver byte-identity
pins rely on.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional, Sequence

from keystone_tpu.analysis import robustness as _robustness
from keystone_tpu.analysis import shapes as _shapes
from keystone_tpu.analysis import signatures as _signatures
from keystone_tpu.analysis.findings import (
    AnalysisReport,
    PipelineValidationError,
)
from keystone_tpu.workflow import graph as G

logger = logging.getLogger(__name__)

ENV_VALIDATE = "KEYSTONE_VALIDATE"

#: the cheap pre-flight set (no solver tracing, no device work beyond
#: an optional stream peek / deadline cost estimate)
DEFAULT_PASSES = ("shapes", "robustness", "signatures")
ALL_PASSES = DEFAULT_PASSES + ("precision", "plan")


def validation_enabled(explicit: Optional[bool] = None) -> bool:
    """Resolve a ``validate=`` parameter: explicit wins; None reads
    ``KEYSTONE_VALIDATE`` (\"1\" = on).  One env lookup when off."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get(ENV_VALIDATE, "0") == "1"


def _as_graph_and_sources(pipeline, example):
    """(graph, {SourceId: abstract}) from a Pipeline or raw Graph."""
    if isinstance(pipeline, G.Graph):
        graph = pipeline
        srcs: Dict = {}
        if example is not None and graph.sources:
            srcs[graph.sources[0]] = _shapes.source_abstract(example)
        return graph, srcs
    graph = pipeline.graph
    srcs = {}
    if example is not None:
        src = getattr(pipeline, "source", None)
        if src is not None:
            srcs[src] = _shapes.source_abstract(example)
    return graph, srcs


def analyze(
    pipeline,
    example=None,
    deadline=None,
    passes: Sequence[str] = DEFAULT_PASSES,
    mode: str = "fit",
    plan_text=_robustness._UNSET,
    breaker_threshold=_robustness._UNSET,
) -> AnalysisReport:
    """Run the requested analyzer passes over ``pipeline`` (a Pipeline,
    PipelineDataset-like graph holder, or raw Graph).

    ``example`` seeds the open source for shape propagation: a Dataset,
    a batch array, a ``jax.ShapeDtypeStruct``, or a per-item shape
    tuple.  ``deadline`` (seconds or ``guard.Deadline``) enables the
    deadline-feasibility estimate.  ``mode="apply"`` marks remaining
    estimators as errors (the freeze/serve contract)."""
    graph, sources = _as_graph_and_sources(pipeline, example)
    report = AnalysisReport()
    for p in passes:
        if p == "shapes":
            report.extend(_shapes.run(graph, sources, mode=mode))
        elif p == "robustness":
            report.extend(
                _robustness.run(
                    graph,
                    deadline=deadline,
                    plan_text=plan_text,
                    breaker_threshold=breaker_threshold,
                )
            )
        elif p == "signatures":
            report.extend(_signatures.run(graph))
        elif p == "precision":
            from keystone_tpu.analysis import precision as _precision

            report.extend(_precision.run())
        elif p == "plan":
            from keystone_tpu.analysis import plan as _plan

            report.extend(
                _plan.run(
                    graph,
                    pipeline=None
                    if isinstance(pipeline, G.Graph)
                    else pipeline,
                )
            )
        else:
            raise ValueError(f"unknown analyzer pass {p!r}; known: {ALL_PASSES}")
    return report


def _log_warnings(report: AnalysisReport, what: str) -> None:
    for f in report.warnings:
        logger.warning("pre-flight %s: %s", what, f.render())


def validate_fit(pipeline, deadline=None, example=None) -> AnalysisReport:
    """The ``Pipeline.fit(validate=…)`` pre-flight: default passes,
    errors raise :class:`PipelineValidationError`, warnings log."""
    report = analyze(
        pipeline, example=example, deadline=deadline, passes=DEFAULT_PASSES
    )
    _log_warnings(report, "fit")
    report.raise_for_errors()
    return report


def validate_freeze(pipeline, example=None) -> AnalysisReport:
    """The ``Pipeline.freeze(validate=…)`` pre-flight: apply-mode
    analysis (unfitted estimators are errors) before the serve path
    primes any bucket program.  Includes the ``plan`` pass — a frozen
    pipeline is about to serve, so a stale or backend-mismatched
    installed plan is worth a warning here."""
    report = analyze(
        pipeline,
        example=example,
        passes=DEFAULT_PASSES + ("plan",),
        mode="apply",
    )
    _log_warnings(report, "freeze")
    report.raise_for_errors()
    return report
