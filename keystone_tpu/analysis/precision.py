"""Pass (b): precision-policy lint over solver jaxprs.

PR 2's guarantee — solver math stays bit-identical f32 under every
``KEYSTONE_MATMUL`` mode — is pinned by byte-identity tests on two
paths.  This pass generalizes the pin into a *checker*: it traces the
jaxpr of every registered solver entry point (``lbfgs`` dense+sparse,
``block_ls``, ``block_weighted_ls``, ``kernel_ridge``) under each
precision mode (``bf16_apply`` force-resolved so the sweep is honest on
CPU), walks every contraction equation — recursing through pjit / scan /
while / cond sub-jaxprs — and errors on:

- ``bf16-solver-input``: a ``dot_general``/conv operand is bfloat16 —
  the apply-side policy leaked into solver math;
- ``non-f32-accumulation``: a contraction's result (or declared
  ``preferred_element_type``) is not f32 — accumulation degraded.

The registry of entry points is data (:data:`SOLVER_ENTRIES`), so a new
solver family is one tuple away from coverage; :func:`check_fn` is the
reusable core (the seeded-defect tests point it at deliberately-bf16
functions).
"""

from __future__ import annotations

import contextlib
import logging
from typing import Callable, List, Optional, Sequence, Tuple

from keystone_tpu.analysis.findings import PASS_PRECISION, Finding

logger = logging.getLogger(__name__)

#: contraction primitives whose operands/accumulation the lint audits
_DOT_PRIMS = ("dot_general", "conv_general_dilated", "ragged_dot")

#: the modes every solver must stay f32 under (the full KEYSTONE_MATMUL
#: surface; "auto" resolves to one of these)
MODES = ("f32", "bf16", "bf16_apply")


def _jaxpr_types():
    """(ClosedJaxpr, Jaxpr) types without reaching into private jax
    modules (layout moved across jax versions)."""
    import jax

    closed = jax.make_jaxpr(lambda: 0)()
    return type(closed), type(closed.jaxpr)


def _iter_eqns(jaxpr, closed_t, jaxpr_t):
    """Yield every equation in ``jaxpr`` and, recursively, in any
    sub-jaxpr carried by equation params (pjit, scan, while, cond)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _as_jaxprs(v, closed_t, jaxpr_t):
                yield from _iter_eqns(sub, closed_t, jaxpr_t)


def _as_jaxprs(v, closed_t, jaxpr_t):
    if isinstance(v, closed_t):
        yield v.jaxpr
    elif isinstance(v, jaxpr_t):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _as_jaxprs(x, closed_t, jaxpr_t)


def _var_dtype(v) -> Optional[str]:
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)


def check_fn(
    fn: Callable, *avals, name: str = "solver", mode: Optional[str] = None
) -> List[Finding]:
    """Trace ``fn`` over ``avals`` (ShapeDtypeStructs) and audit every
    contraction equation.  ``mode`` labels the findings; the caller owns
    setting the precision policy before tracing."""
    import jax

    closed_t, jaxpr_t = _jaxpr_types()
    # a FRESH function object per call: jax caches traces by (fun,
    # avals), and the precision policy is read at trace time — reusing
    # a cached jaxpr across the mode sweep would audit mode 1's graph
    # three times and make the sweep vacuous
    closed = jax.make_jaxpr(lambda *a: fn(*a))(*avals)
    findings: List[Finding] = []
    tag = f"{name}" + (f" under mode={mode}" if mode else "")
    for eqn in _iter_eqns(closed.jaxpr, closed_t, jaxpr_t):
        if eqn.primitive.name not in _DOT_PRIMS:
            continue
        for v in eqn.invars:
            dt = _var_dtype(v)
            if dt == "bfloat16":
                findings.append(
                    Finding(
                        "error",
                        PASS_PRECISION,
                        "bf16-solver-input",
                        f"{tag}: {eqn.primitive.name} consumes a bfloat16 "
                        "operand — the apply-side precision policy leaked "
                        "into solver math (use utils.precision.sdot)",
                        label=name,
                    )
                )
                break
        pet = eqn.params.get("preferred_element_type")
        out_dt = _var_dtype(eqn.outvars[0]) if eqn.outvars else None
        bad_pet = pet is not None and "float32" not in str(pet) and "float64" not in str(pet)
        bad_out = out_dt is not None and out_dt not in ("float32", "float64")
        if bad_pet or bad_out:
            findings.append(
                Finding(
                    "error",
                    PASS_PRECISION,
                    "non-f32-accumulation",
                    f"{tag}: {eqn.primitive.name} accumulates in "
                    f"{pet if bad_pet else out_dt} — solver contractions "
                    "must accumulate (and emit) f32",
                    label=name,
                )
            )
    return findings


# --------------------------------------------------------------- registry


def _avals(*specs):
    """ShapeDtypeStructs from (shape, dtype) pairs."""
    import jax
    import numpy as np

    return tuple(jax.ShapeDtypeStruct(s, np.dtype(d)) for s, d in specs)


def _entry_lbfgs_dense():
    from keystone_tpu.models.lbfgs import _lbfgs_least_squares

    fn = lambda x, y, n, lam: _lbfgs_least_squares(  # noqa: E731
        x, y, n, lam, num_iterations=2, history=3, fit_intercept=True
    )
    return fn, _avals(((8, 4), "f4"), ((8, 2), "f4"), ((), "f4"), ((), "f4"))


def _entry_lbfgs_sparse():
    from keystone_tpu.models.lbfgs import _sparse_vag

    fn = lambda idx, vals, y, n, lam, w: _sparse_vag(  # noqa: E731
        ((idx,), (vals,), (y,), n, lam), w, d=5, intercept=False
    )
    return fn, _avals(
        ((8, 3), "i4"),
        ((8, 3), "f4"),
        ((8, 2), "f4"),
        ((), "f4"),
        ((), "f4"),
        ((5, 2), "f4"),
    )


def _entry_block_ls():
    from keystone_tpu.models.block_ls import _oc_block_step

    return _oc_block_step, _avals(
        ((8, 4), "f4"),
        ((4,), "f4"),
        ((8, 2), "f4"),
        ((8,), "f4"),
        ((8,), "f4"),
        ((8, 2), "f4"),
        ((4, 2), "f4"),
        ((), "f4"),
    )


def _entry_block_weighted_ls():
    from keystone_tpu.models.block_weighted_ls import _weighted_bcd_fit

    fn = lambda x, y, alpha, n, lam: _weighted_bcd_fit(  # noqa: E731
        x, y, alpha, n, lam, 1, 4, True
    )
    return fn, _avals(
        ((8, 4), "f4"), ((8, 2), "f4"), ((8,), "f4"), ((), "f4"), ((), "f4")
    )


def _entry_kernel_ridge():
    from keystone_tpu.models.kernel_ridge import _krr_fit

    fn = lambda x, y, n: _krr_fit(x, y, n, 0.5, 1e-3, 4, 2)  # noqa: E731
    return fn, _avals(((8, 4), "f4"), ((8, 2), "f4"), ((), "f4"))


def _entry_kernel_ridge_oc():
    """The out-of-core gram-block sweep: one diag (solve) step chained
    into one off-diag F update — the two jitted programs the streamed
    fit dispatches.  Traced with use_pallas=False: the lint runs on CPU
    and audits the XLA chain; the Pallas path accumulates f32 in VMEM
    by construction and carries no dot_general to audit."""
    from keystone_tpu.models.kernel_ridge import (
        _oc_krr_diag_step,
        _oc_krr_offdiag_step,
    )

    def fn(xb, fb, ab, yb, ok_b, lam_n, xi, fi):
        ab2, fb2, dab, _ = _oc_krr_diag_step(
            xb, fb, ab, yb, ok_b, lam_n, gamma=0.5, use_pallas=False
        )
        fi2, _ = _oc_krr_offdiag_step(
            fi, xi, xb, dab, ok_b, ok_b, gamma=0.5, use_pallas=False
        )
        return ab2, fb2, fi2

    return fn, _avals(
        ((8, 4), "f4"),
        ((8, 2), "f4"),
        ((8, 2), "f4"),
        ((8, 2), "f4"),
        ((8,), "f4"),
        ((), "f4"),
        ((8, 4), "f4"),
        ((8, 2), "f4"),
    )


def _entry_nystrom():
    from keystone_tpu.models.nystrom import _nystrom_whiten

    fn = lambda l, g, r: _nystrom_whiten(l, g, r)  # noqa: E731
    return fn, _avals(((8, 4), "f4"), ((), "f4"), ((), "f4"))


#: (name, builder) — builder returns (traceable fn, input avals).  Every
#: solver family the repo ships must appear here; the seeded-defect
#: tests assert the checker catches a planted bf16 leak via check_fn.
SOLVER_ENTRIES: Sequence[Tuple[str, Callable]] = (
    ("lbfgs.dense", _entry_lbfgs_dense),
    ("lbfgs.sparse", _entry_lbfgs_sparse),
    ("block_ls", _entry_block_ls),
    ("block_weighted_ls", _entry_block_weighted_ls),
    ("kernel_ridge", _entry_kernel_ridge),
    ("kernel_ridge.oc", _entry_kernel_ridge_oc),
    ("nystrom", _entry_nystrom),
)


def _mode_context(mode: str):
    from keystone_tpu.utils import precision

    if mode == "bf16_apply":
        ctx = contextlib.ExitStack()
        ctx.enter_context(precision.matmul("bf16_apply"))
        # force-resolve the policy ACTIVE off-TPU: the sweep must audit
        # the graph a real TPU would run, not the CPU-inert fallback
        ctx.enter_context(precision.force_bf16_apply())
        return ctx
    return precision.matmul(mode)


def run(modes: Sequence[str] = MODES) -> List[Finding]:
    """Audit every registered solver entry point under every mode."""
    findings: List[Finding] = []
    for name, build in SOLVER_ENTRIES:
        try:
            fn, avals = build()
        except Exception as e:
            findings.append(
                Finding(
                    "warning",
                    PASS_PRECISION,
                    "solver-entry-unavailable",
                    f"solver entry {name} could not be built for "
                    f"tracing: {type(e).__name__}: {e}",
                    label=name,
                )
            )
            continue
        for mode in modes:
            try:
                with _mode_context(mode):
                    findings.extend(
                        check_fn(fn, *avals, name=name, mode=mode)
                    )
            except Exception as e:
                findings.append(
                    Finding(
                        "warning",
                        PASS_PRECISION,
                        "solver-entry-untraceable",
                        f"solver entry {name} failed to trace under "
                        f"mode={mode}: {type(e).__name__}: {e}",
                        label=name,
                    )
                )
    return findings
