"""Pass (d): CSE / cache-signature audit.

``signature()`` is load-bearing identity across the stack: the CSE rule
merges equal-prefix nodes, the shared-apply program caches key on
``(class, params())``, saved-state reload and the executor's breaker
registry both derive keys from it.  A transformer whose ``params()``
under-specifies its behavior — two observably different instances with
equal signatures — therefore doesn't just miss an optimization: CSE
silently replaces one node with the other, and cached programs/breaker
state leak between them (the PR-4 breaker-key collision class, caught
here statically).

Findings:

- ``signature-collision`` (error): two distinct transformer/estimator
  instances in the graph report equal signatures but differ in
  observable state (a public scalar/tuple attribute, or an array
  attribute's shape/dtype/small-value content);
- ``unstable-signature`` (error): ``signature()`` raises, is
  unhashable, or returns different values on consecutive calls —
  every signature consumer assumes stable hashable identity;
- ``dataset-name-collision`` (error): two distinct bound datasets share
  a ``name`` (the cross-process CSE/saved-state identity) but disagree
  on payload length/kind.
"""

from __future__ import annotations

import logging
from typing import List

from keystone_tpu.analysis.findings import PASS_SIGNATURES, Finding
from keystone_tpu.workflow import graph as G

logger = logging.getLogger(__name__)

#: instance attributes that are caches/plumbing, never identity
_SKIP_ATTRS = {"_fp", "_jitted", "_breaker_token", "fallback", "optional"}

_SIMPLE = (int, float, str, bool, bytes, type(None))

#: value-compare arrays up to this many elements (device→host read is
#: bounded); larger arrays compare by shape/dtype only
_VALUE_COMPARE_MAX = 4096


def _state_conflict(a, b) -> str:
    """Name of the first observable state difference between two
    equal-signature instances, or '' when none is detectable."""
    import numpy as np

    va = {k: v for k, v in vars(a).items() if k not in _SKIP_ATTRS}
    vb = {k: v for k, v in vars(b).items() if k not in _SKIP_ATTRS}
    for k in sorted(set(va) | set(vb)):
        if k.startswith("__"):
            continue
        x, y = va.get(k, _MISSING), vb.get(k, _MISSING)
        if x is _MISSING or y is _MISSING:
            return k
        if isinstance(x, _SIMPLE) or isinstance(y, _SIMPLE):
            if type(x) is not type(y) or x != y:
                return k
            continue
        if isinstance(x, tuple) and isinstance(y, tuple):
            if x != y:
                return k
            continue
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            if not (hasattr(y, "shape") and hasattr(y, "dtype")):
                return k
            if tuple(x.shape) != tuple(y.shape) or str(x.dtype) != str(
                y.dtype
            ):
                return k
            try:
                if (
                    int(np.prod(x.shape)) <= _VALUE_COMPARE_MAX
                    and not np.array_equal(
                        np.asarray(x, np.float64), np.asarray(y, np.float64)
                    )
                ):
                    return k
            except (TypeError, ValueError):
                pass
            continue
        # opaque objects: type change is observable, content is not
        if type(x) is not type(y):
            return k
    return ""


class _Missing:
    pass


_MISSING = _Missing()


def _stable_signature(obj, n, label, findings: List[Finding]):
    """signature() if stable+hashable, else None (with a finding)."""
    try:
        s1 = obj.signature()
        s2 = obj.signature()
        if s1 is not None:
            hash(s1)
    except Exception as e:
        findings.append(
            Finding(
                "error",
                PASS_SIGNATURES,
                "unstable-signature",
                f"{label}.signature() raised or is unhashable "
                f"({type(e).__name__}: {e}); every CSE/cache/breaker "
                "consumer assumes stable hashable identity",
                node=n.id,
                label=label,
            )
        )
        return None
    if s1 != s2:
        findings.append(
            Finding(
                "error",
                PASS_SIGNATURES,
                "unstable-signature",
                f"{label}.signature() returns different values on "
                "consecutive calls; identity must be deterministic",
                node=n.id,
                label=label,
            )
        )
        return None
    return s1


def collision_signatures(graph: G.Graph) -> set:
    """The set of transformer/estimator ``signature()`` values that
    COLLIDE in ``graph``: ≥ 2 distinct instances report the signature
    while differing in observable state.

    This is the cross-pipeline sharing admission gate
    (``workflow/cross.py``): the planner unions every co-served tenant
    graph and refuses to mark any stage whose signature lands in this
    set — a collision means ``params()`` under-specifies behavior, so a
    shared-pool entry for one instance would silently answer for the
    other.  Unstable/raising signatures are treated as colliding too
    (identity that cannot be trusted cannot key a shared cache)."""
    colliding: set = set()
    by_sig: dict = {}
    for n in graph.topological_nodes():
        op = graph.operators[n]
        if isinstance(op, G.TransformerOperator):
            obj = op.transformer
        elif isinstance(op, G.EstimatorOperator):
            obj = op.estimator
        else:
            continue
        try:
            s1 = obj.signature()
            s2 = obj.signature()
            if s1 is not None:
                hash(s1)
        except Exception:
            # raising/unhashable identity: nothing to key a refusal by
            # — the planner's own (guarded) signature() call yields
            # None for such nodes, so they are never pooled anyway
            continue
        if s1 is None:
            continue  # params() is None: never pooled
        if s1 != s2:
            # unstable identity cannot be trusted to key a shared
            # cache: refuse BOTH observed values
            colliding.add(s1)
            try:
                colliding.add(s2)
            except TypeError:
                pass
            continue
        by_sig.setdefault(s1, []).append(obj)
    for sig, group in by_sig.items():
        if len(group) < 2:
            continue
        first = group[0]
        for other in group[1:]:
            if other is first:
                continue
            if _state_conflict(first, other):
                colliding.add(sig)
                break
    return colliding


def run(graph: G.Graph) -> List[Finding]:
    findings: List[Finding] = []
    by_sig: dict = {}
    datasets_by_name: dict = {}
    for n in graph.topological_nodes():
        op = graph.operators[n]
        if isinstance(op, G.TransformerOperator):
            obj = op.transformer
        elif isinstance(op, G.EstimatorOperator):
            obj = op.estimator
        elif isinstance(op, G.DatasetOperator):
            ds = op.dataset
            name = getattr(ds, "name", None)
            if name is not None:
                prev = datasets_by_name.get(name)
                if prev is not None and prev[1] is not ds:
                    pn, pds = prev
                    if (
                        getattr(pds, "n", None) != getattr(ds, "n", None)
                        or getattr(pds, "is_host", None)
                        != getattr(ds, "is_host", None)
                    ):
                        findings.append(
                            Finding(
                                "error",
                                PASS_SIGNATURES,
                                "dataset-name-collision",
                                f"datasets at n{pn.id} and n{n.id} share "
                                f"name {name!r} but differ in payload "
                                "(names are cross-process CSE/saved-state "
                                "identity)",
                                node=n.id,
                                label=op.label(),
                            )
                        )
                else:
                    datasets_by_name[name] = (n, ds)
            continue
        else:
            continue
        sig = _stable_signature(obj, n, op.label(), findings)
        if sig is None:
            continue
        by_sig.setdefault(sig, []).append((n, obj, op.label()))

    for sig, group in by_sig.items():
        if len(group) < 2:
            continue
        n0, obj0, label0 = group[0]
        for n1, obj1, label1 in group[1:]:
            if obj1 is obj0:
                continue  # literally the same instance: the intended case
            attr = _state_conflict(obj0, obj1)
            if attr:
                findings.append(
                    Finding(
                        "error",
                        PASS_SIGNATURES,
                        "signature-collision",
                        f"{label0} at n{n0.id} and n{n1.id} report equal "
                        f"signatures but differ in attribute {attr!r}: "
                        "CSE would merge them and shared program/breaker "
                        "caches would leak between them — include the "
                        "attribute in params()",
                        node=n1.id,
                        label=label1,
                    )
                )
    return findings
