"""Bundled-pipeline builders for the pre-flight analyzer.

``cli.py check <PipelineName>`` (and the analyzer's false-positive gate
in tests/test_analysis.py) need every bundled pipeline *constructed* —
graph assembled, estimators unbound — without running a fit.  Each
builder here instantiates the app's own ``build()`` over tiny synthetic
loader data (the same path tests/test_pipelines.py exercises end to
end, scaled down: graph construction is cheap; only RandomPatchCifar's
imperative feature learning touches the device, on a few dozen rows).

Returns ``(pipeline, example)`` where ``example`` is the training-data
Dataset — the input spec the shapes pass seeds the open source with.
"""

from __future__ import annotations

from typing import Tuple


def _mnist():
    from keystone_tpu.loaders.mnist import MnistLoader
    from keystone_tpu.pipelines.mnist_random_fft import MnistRandomFFT

    cfg = MnistRandomFFT.Config(num_ffts=2, synthetic_n=128)
    train = MnistLoader.synthetic(cfg.synthetic_n, seed=1)
    return MnistRandomFFT.build(cfg, train.data, train.labels), train.data


def _linear_pixels():
    from keystone_tpu.loaders.cifar import CifarLoader
    from keystone_tpu.pipelines.linear_pixels import LinearPixels

    cfg = LinearPixels.Config(synthetic_n=128)
    train = CifarLoader.synthetic(cfg.synthetic_n, seed=1)
    return LinearPixels.build(cfg, train.data, train.labels), train.data


def _random_patch_cifar():
    from keystone_tpu.loaders.cifar import CifarLoader
    from keystone_tpu.pipelines.random_patch_cifar import RandomPatchCifar

    cfg = RandomPatchCifar.Config(
        num_filters=32,
        patches_per_image=2,
        block_size=128,
        num_iter=1,
        synthetic_n=64,
    )
    train = CifarLoader.synthetic(cfg.synthetic_n, seed=1)
    return RandomPatchCifar.build(cfg, train.data, train.labels), train.data


def _newsgroups():
    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.pipelines.newsgroups import NewsgroupsPipeline

    cfg = NewsgroupsPipeline.Config(
        num_features=512, head="nb", num_classes=4, synthetic_n=120
    )
    train = NewsgroupsDataLoader.synthetic(
        cfg.synthetic_n, cfg.num_classes, seed=1
    )
    return NewsgroupsPipeline.build(cfg, train.data, train.labels), train.data


def _timit():
    from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
    from keystone_tpu.pipelines.timit import TimitPipeline

    cfg = TimitPipeline.Config(
        num_cosine_features=256,
        cosine_block_size=128,
        num_epochs=1,
        num_classes=8,
        synthetic_n=256,
    )
    train = TimitFeaturesDataLoader.synthetic(
        cfg.synthetic_n, cfg.num_classes, seed=1
    )
    return TimitPipeline.build(cfg, train.data, train.labels), train.data


def _imagenet():
    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.pipelines.imagenet_sift_lcs_fv import ImageNetSiftLcsFV

    cfg = ImageNetSiftLcsFV.Config(
        num_classes=4,
        gmm_k=4,
        gmm_iters=2,
        pca_dims=16,
        descriptor_samples_per_image=16,
        solver_block_size=256,
        synthetic_n=24,
        image_size=48,
        sift_step=8,
        lcs_step=8,
    )
    train = ImageNetLoader.synthetic(
        cfg.synthetic_n,
        cfg.num_classes,
        size=(cfg.image_size, cfg.image_size),
        seed=1,
    )
    return (
        ImageNetSiftLcsFV.build(cfg, train.data, train.labels),
        train.data,
    )


def _voc():
    from keystone_tpu.loaders.voc import VOCLoader
    from keystone_tpu.pipelines.voc_sift_fisher import VOCSIFTFisher

    cfg = VOCSIFTFisher.Config(
        gmm_k=4,
        gmm_iters=2,
        pca_dims=16,
        descriptor_samples_per_image=16,
        solver_block_size=256,
        synthetic_n=16,
        image_size=48,
        sift_step=8,
    )
    train = VOCLoader.synthetic(
        cfg.synthetic_n, size=(cfg.image_size, cfg.image_size), seed=1
    )
    return VOCSIFTFisher.build(cfg, train.data, train.labels), train.data


def _amazon():
    from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader
    from keystone_tpu.pipelines.amazon_reviews import AmazonReviewsPipeline

    cfg = AmazonReviewsPipeline.Config(
        num_features=1024, ngrams=2, num_iters=4, synthetic_n=120
    )
    train = AmazonReviewsDataLoader.synthetic(cfg.synthetic_n, seed=1)
    return (
        AmazonReviewsPipeline.build(cfg, train.data, train.labels),
        train.data,
    )


def _kernel_timit():
    from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
    from keystone_tpu.pipelines.kernel_timit import KernelTimitPipeline

    cfg = KernelTimitPipeline.Config(
        num_landmarks=64,
        solver_block_size=64,
        num_epochs=1,
        num_classes=8,
        synthetic_n=256,
    )
    train = TimitFeaturesDataLoader.synthetic(
        cfg.synthetic_n, cfg.num_classes, seed=1
    )
    return (
        KernelTimitPipeline.build(cfg, train.data, train.labels),
        train.data,
    )


def _kernel_cifar():
    from keystone_tpu.loaders.cifar import CifarLoader
    from keystone_tpu.pipelines.kernel_cifar import KernelCifarPipeline

    cfg = KernelCifarPipeline.Config(
        num_landmarks=48,
        solver_block_size=48,
        num_epochs=1,
        synthetic_n=96,
    )
    train = CifarLoader.synthetic(cfg.synthetic_n, seed=1)
    return (
        KernelCifarPipeline.build(cfg, train.data, train.labels),
        train.data,
    )


_BUILDERS = {
    "MnistRandomFFT": _mnist,
    "LinearPixels": _linear_pixels,
    "RandomPatchCifar": _random_patch_cifar,
    "NewsgroupsPipeline": _newsgroups,
    "TimitPipeline": _timit,
    "ImageNetSiftLcsFV": _imagenet,
    "VOCSIFTFisher": _voc,
    "AmazonReviewsPipeline": _amazon,
    "KernelTimitPipeline": _kernel_timit,
    "KernelCifarPipeline": _kernel_cifar,
}

BUNDLED = tuple(_BUILDERS)


def build_bundled(name: str) -> Tuple[object, object]:
    """(pipeline, example Dataset) for one bundled app, assembled over
    tiny synthetic data — the ``cli.py check`` construction path."""
    try:
        builder = _BUILDERS[name]
    except KeyError:
        raise ValueError(
            f"unknown bundled pipeline {name!r}; known: {sorted(_BUILDERS)}"
        ) from None
    return builder()
