"""Typed findings — the one output schema every analyzer pass shares.

The reference's static guarantee is Scala's type system: a mis-wired
``Transformer`` chain does not compile (PAPER.md § workflow.Pipeline).
The jax_graft port replaces the compiler with this schema: each pass
(``analysis.shapes`` / ``precision`` / ``robustness`` / ``signatures``)
emits :class:`Finding` records with a severity, a stable machine code,
and a graph location, and :class:`AnalysisReport` aggregates them —
renderable for the CLI, raisable for ``Pipeline.fit(validate=)``, and
overlayable onto the DOT graph (``workflow/viz.to_dot(findings=)``).

Severities:

- ``error``   — the pipeline WILL misbehave (mis-shaped stage, unfitted
  estimator reference, signature collision, bf16 leaking into solver
  math).  ``AnalysisReport.raise_for_errors`` turns these into
  :class:`PipelineValidationError`; ``cli.py check`` exits non-zero.
- ``warning`` — probably not what the author meant (silent f64→f32
  downcast, infeasible deadline budget, mandatory stage under breaker
  supervision with no fallback).  Logged, never raised.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

SEVERITIES = ("error", "warning")

#: pass identifiers (the tentpole's a–d, plus the planner audit)
PASS_SHAPES = "shapes"
PASS_PRECISION = "precision"
PASS_ROBUSTNESS = "robustness"
PASS_SIGNATURES = "signatures"
PASS_PLAN = "plan"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer observation, anchored to a graph location."""

    severity: str  # "error" | "warning"
    pass_id: str  # "shapes" | "precision" | "robustness" | "signatures"
    code: str  # stable kebab-case code, e.g. "shape-mismatch"
    message: str
    #: NodeId.id of the offending node (None = whole-graph finding)
    node: Optional[int] = None
    #: operator label at that node (for humans; labels can repeat)
    label: Optional[str] = None

    def location(self) -> str:
        if self.node is None:
            return "<graph>"
        if self.label:
            return f"n{self.node}[{self.label}]"
        return f"n{self.node}"

    def render(self) -> str:
        return (
            f"{self.severity.upper():7s} {self.pass_id}/{self.code} "
            f"at {self.location()}: {self.message}"
        )


class AnalysisReport:
    """Ordered findings from one :func:`~keystone_tpu.analysis.analyze`
    run.  Errors first in :meth:`render`; insertion order otherwise."""

    def __init__(self, findings: Sequence[Finding] = ()):
        self.findings: List[Finding] = list(findings)

    # ------------------------------------------------------------ views
    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warning"]

    @property
    def ok(self) -> bool:
        """No errors (warnings do not fail a pre-flight)."""
        return not self.errors

    def __len__(self) -> int:
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    # ----------------------------------------------------------- output
    def render(self) -> str:
        """Human-readable listing, errors first."""
        if not self.findings:
            return "analysis: no findings"
        lines = [
            f.render()
            for f in sorted(
                self.findings, key=lambda f: SEVERITIES.index(f.severity)
            )
        ]
        lines.append(
            f"analysis: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s)"
        )
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "errors": len(self.errors),
            "warnings": len(self.warnings),
        }

    def raise_for_errors(self) -> None:
        """Raise :class:`PipelineValidationError` when any error-severity
        finding is present; warnings never raise."""
        if self.errors:
            raise PipelineValidationError(self)

    def __repr__(self):
        return (
            f"AnalysisReport(errors={len(self.errors)}, "
            f"warnings={len(self.warnings)})"
        )


class PipelineValidationError(ValueError):
    """The pre-flight analyzer found error-severity findings; the
    pipeline was refused before any device work.  Carries the full
    :class:`AnalysisReport` as ``.report``."""

    def __init__(self, report: AnalysisReport):
        self.report = report
        super().__init__(
            "pipeline failed static validation:\n" + report.render()
        )
