"""Pass (c): robustness lint — fault plans, breakers, deadline budgets.

PR 1/4/5 gave the stack named fault sites, per-node circuit breakers,
and deadline apportionment; this pass checks their *configuration*
statically, before a fit spends minutes discovering it:

- ``bad-fault-plan`` (error): the active ``KEYSTONE_FAULTS`` plan (or a
  plan the caller passes) names a site that matches no registered site,
  or fails to parse — a typo'd site never fires and reports nothing
  outside ``tools/chaos.py``'s exit-2 path;
- ``mandatory-under-breaker`` (warning): breaker supervision is
  configured (``KEYSTONE_BREAKER_THRESHOLD``) but mandatory stages —
  no ``optional=True``, no ``with_fallback`` — dominate the graph: one
  open breaker fails the whole run.  Emitted once, listing the labels;
- ``deadline-infeasible`` / ``stage-deadline-infeasible`` (warnings):
  the requested deadline (or the ``KEYSTONE_STAGE_DEADLINE`` per-stage
  cap) is below the ``ProfilingAutoCacheRule`` cost estimates for the
  graph — the fit is configured to be killed by its own watchdogs.
  Cost estimation samples stages (cheap, but real device work), so it
  runs only when the caller supplies a deadline.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

from keystone_tpu.analysis.findings import PASS_ROBUSTNESS, Finding
from keystone_tpu.workflow import graph as G

logger = logging.getLogger(__name__)

_UNSET = object()


def run(
    graph: G.Graph,
    deadline=None,
    plan_text=_UNSET,
    breaker_threshold=_UNSET,
    estimate_costs: Optional[bool] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_check_fault_plan(plan_text))
    findings.extend(_check_breakers(graph, breaker_threshold))
    if estimate_costs is None:
        estimate_costs = deadline is not None
    if estimate_costs and deadline is not None:
        findings.extend(_check_deadline(graph, deadline))
    return findings


def _check_fault_plan(plan_text) -> List[Finding]:
    from keystone_tpu import faults

    if plan_text is _UNSET:
        plan_text = os.environ.get(faults.ENV_VAR)
    if not plan_text:
        return []
    try:
        plan = (
            plan_text
            if isinstance(plan_text, faults.FaultPlan)
            else faults.parse_plan(plan_text)
        )
        faults.validate_plan(plan)
    except faults.FaultPlanError as e:
        return [
            Finding(
                "error",
                PASS_ROBUSTNESS,
                "bad-fault-plan",
                f"active fault plan is invalid: {e}",
            )
        ]
    return []


def _check_breakers(graph: G.Graph, breaker_threshold) -> List[Finding]:
    from keystone_tpu.utils import guard
    from keystone_tpu.workflow.executor import _degradable

    if breaker_threshold is _UNSET:
        breaker_threshold = guard.stage_breaker_threshold()
    if breaker_threshold is None:
        return []
    mandatory = []
    for n in graph.topological_nodes():
        op = graph.operators[n]
        if not isinstance(op, G.TransformerOperator):
            continue
        from keystone_tpu.workflow.transformer import Cacher

        if isinstance(op.transformer, Cacher):
            continue
        if _degradable(op) is None:
            mandatory.append(op.label())
    if not mandatory:
        return []
    shown = ", ".join(mandatory[:8]) + ("…" if len(mandatory) > 8 else "")
    return [
        Finding(
            "warning",
            PASS_ROBUSTNESS,
            "mandatory-under-breaker",
            f"breaker supervision is on (threshold="
            f"{breaker_threshold}) but {len(mandatory)} stage(s) declare "
            f"no optional=True/with_fallback degradation ({shown}); an "
            "open breaker fails the whole run (CircuitOpenError)",
        )
    ]


def _check_deadline(graph: G.Graph, deadline) -> List[Finding]:
    from keystone_tpu.utils import guard
    from keystone_tpu.workflow import profiling

    dl = guard.as_deadline(deadline)
    findings: List[Finding] = []
    try:
        profiles = profiling.profile_graph(graph, sample_size=16, static_cost=True)
    except Exception as e:  # cost estimation is best-effort, like the rule
        logger.debug("deadline feasibility profiling failed: %s", e)
        return findings
    if not profiles:
        return findings
    total = sum(p.full_seconds for p in profiles.values())
    remaining = dl.remaining()
    if total > remaining:
        findings.append(
            Finding(
                "warning",
                PASS_ROBUSTNESS,
                "deadline-infeasible",
                f"deadline budget {remaining:.2f}s is below the "
                f"estimated stage cost {total:.2f}s "
                "(ProfilingAutoCacheRule estimates; transformer stages "
                "only — estimator fits ride on top): the run is "
                "configured to be killed by its own watchdog",
            )
        )
    stage_cap = guard.stage_deadline_seconds()
    if stage_cap is not None:
        worst_n, worst = max(
            profiles.items(), key=lambda kv: kv[1].full_seconds
        )
        if worst.full_seconds > stage_cap:
            op = graph.operators.get(worst_n)
            findings.append(
                Finding(
                    "warning",
                    PASS_ROBUSTNESS,
                    "stage-deadline-infeasible",
                    f"KEYSTONE_STAGE_DEADLINE={stage_cap:g}s is below "
                    f"the estimated {worst.full_seconds:.2f}s of the "
                    "most expensive stage",
                    node=worst_n.id,
                    label=None if op is None else op.label(),
                )
            )
    return findings
