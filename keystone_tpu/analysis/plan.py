"""Analyzer pass ``plan``: audit the installed physical plan against
the graph it is about to serve.

The cost-based planner (``keystone_tpu.planner``) pins operator
variants and serving knobs at ``freeze()`` time; this pass is the
pre-flight that catches the two ways a shipped plan goes wrong later:

- ``stale-plan`` — the plan's stage signatures no longer match the
  graph (the model was refit, a stage was swapped, or a plan from a
  different pipeline was installed).  Dispatch sites fall back to the
  static defaults for unmatched stages, so this is a *warning*: the
  pipeline still serves, just not the measured configuration.
- ``bad-plan-candidate`` — the plan names a gate, winner, or knob the
  registry rejects on this backend (a TPU plan on a CPU host, a
  hand-edited ``plan.json``, version skew in the gate table).  Also a
  warning: :func:`~keystone_tpu.planner.registry.planned_gate`
  re-validates at dispatch and ignores unusable winners.

With no plan installed (and none passed) the pass is inert — zero
findings, zero imports beyond the registry probe — preserving the
no-plan byte-identity guarantee.
"""

from __future__ import annotations

from typing import List, Optional

from keystone_tpu.analysis.findings import PASS_PLAN, Finding


def run(graph, pipeline=None, plan=None) -> List[Finding]:
    """Audit ``plan`` (default: the process-installed plan) against
    ``graph``.  ``pipeline`` (optional) enables the whole-pipeline
    signature check for the matmul stage."""
    from keystone_tpu.planner import registry

    if plan is None:
        plan = registry.current_plan()
    if plan is None:
        return []

    findings: List[Finding] = []

    # graph-independent: gates, winners, knobs vs the registry tables
    for code, msg in plan.validate(backend=registry.current_backend()):
        findings.append(
            Finding(
                severity="warning",
                pass_id=PASS_PLAN,
                code=code,
                message=msg,
            )
        )

    # graph-dependent: every per-stage choice must anchor to a stage
    # that is actually in this graph
    sigs, labels = _graph_signatures(graph)
    psig = _pipeline_signature(pipeline)
    for s in plan.stages:
        if s.signature.startswith("pipeline"):
            # the whole-pipeline matmul stage: compare fingerprints
            if (
                psig
                and plan.pipeline_signature
                and plan.pipeline_signature != psig
            ):
                findings.append(
                    Finding(
                        severity="warning",
                        pass_id=PASS_PLAN,
                        code="stale-plan",
                        message=(
                            f"plan was sampled on pipeline "
                            f"{plan.pipeline_signature[:12]} but this "
                            f"pipeline fingerprints as {psig[:12]}; "
                            f"re-plan at freeze()"
                        ),
                    )
                )
            continue
        if s.signature not in sigs:
            hint = ""
            if s.label in labels:
                hint = (
                    f" (a {s.label!r} stage exists but its parameters "
                    f"changed since sampling)"
                )
            findings.append(
                Finding(
                    severity="warning",
                    pass_id=PASS_PLAN,
                    code="stale-plan",
                    message=(
                        f"plan stage {s.label!r} [{s.signature}] for gate "
                        f"{s.gate!r} is not in this graph{hint}; the "
                        f"static default serves it"
                    ),
                )
            )
    return findings


def _graph_signatures(graph):
    """(signatures, labels) of every transformer-backed node — the
    anchor set plan stages must land in."""
    from keystone_tpu.planner.plan import stage_signature

    sigs, labels = set(), set()
    for node in getattr(graph, "operators", {}):
        op = graph.operators.get(node)
        t = getattr(op, "transformer", None)
        if t is None:
            continue
        try:
            sigs.add(stage_signature(t))
            labels.add(type(t).__name__)
        except Exception:
            continue
    return sigs, labels


def _pipeline_signature(pipeline) -> Optional[str]:
    if pipeline is None:
        return None
    try:
        from keystone_tpu.utils.hashing import pipeline_fingerprint

        return pipeline_fingerprint(pipeline)
    except Exception:
        return None
